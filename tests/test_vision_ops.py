"""paddle.vision.ops detection primitives + lu_unpack (reference:
``python/paddle/vision/ops.py`` CUDA nms/roi_align kernels,
``paddle.linalg.lu_unpack``). Oracles: brute-force numpy."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as vops


def _t(x):
    return paddle.to_tensor(np.asarray(x))


def _nms_oracle(boxes, scores, thr):
    order = np.argsort(-scores)
    keep = []
    while len(order):
        i = order[0]
        keep.append(i)
        rest = order[1:]
        xx1 = np.maximum(boxes[i, 0], boxes[rest, 0])
        yy1 = np.maximum(boxes[i, 1], boxes[rest, 1])
        xx2 = np.minimum(boxes[i, 2], boxes[rest, 2])
        yy2 = np.minimum(boxes[i, 3], boxes[rest, 3])
        w = np.maximum(0, xx2 - xx1)
        h = np.maximum(0, yy2 - yy1)
        inter = w * h
        a_i = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
        a_r = ((boxes[rest, 2] - boxes[rest, 0]) *
               (boxes[rest, 3] - boxes[rest, 1]))
        iou = inter / (a_i + a_r - inter)
        order = rest[iou <= thr]
    return keep


class TestNMS:
    @pytest.mark.slow  # 7 s brute-force duplicate: top_k/multiclass/iou reps
    # below run by default (870s cap)
    def test_matches_bruteforce(self):
        rng = np.random.RandomState(0)
        xy = rng.rand(30, 2) * 60
        wh = rng.rand(30, 2) * 30 + 2
        boxes = np.concatenate([xy, xy + wh], -1).astype(np.float32)
        scores = rng.rand(30).astype(np.float32)
        got = vops.nms(_t(boxes), 0.4, _t(scores)).numpy()
        expect = _nms_oracle(boxes, scores, 0.4)
        np.testing.assert_array_equal(got, expect)

    def test_top_k_padding(self):
        boxes = np.array([[0, 0, 10, 10], [0, 0, 10, 10], [50, 50, 60, 60]],
                         np.float32)
        scores = np.array([0.9, 0.8, 0.7], np.float32)
        got = vops.nms(_t(boxes), 0.5, _t(scores), top_k=3).numpy()
        np.testing.assert_array_equal(got, [0, 2, -1])  # 1 suppressed by 0

    def test_multiclass_suppresses_per_category(self):
        boxes = np.array([[0, 0, 10, 10], [0, 0, 10, 10]], np.float32)
        scores = np.array([0.9, 0.8], np.float32)
        cats = np.array([0, 1])
        got = vops.nms(_t(boxes), 0.5, _t(scores), category_idxs=_t(cats),
                       top_k=2).numpy()
        np.testing.assert_array_equal(got, [0, 1])  # different class: kept

    def test_box_iou_and_area(self):
        a = np.array([[0, 0, 10, 10]], np.float32)
        b = np.array([[5, 5, 15, 15], [20, 20, 30, 30]], np.float32)
        iou = vops.box_iou(_t(a), _t(b)).numpy()
        np.testing.assert_allclose(iou, [[25.0 / 175.0, 0.0]], rtol=1e-5)
        np.testing.assert_allclose(vops.box_area(_t(b)).numpy(), [100, 100])


class TestRoiAlign:
    @pytest.mark.slow  # 9 s RoiAlign duplicate: test_gradient_ramp below is
    # the default rep (870s cap)
    def test_constant_map_returns_constant(self):
        x = np.full((1, 3, 16, 16), 7.0, np.float32)
        rois = np.array([[2, 2, 10, 10]], np.float32)
        out = vops.roi_align(_t(x), _t(rois), output_size=4).numpy()
        assert out.shape == (1, 3, 4, 4)
        np.testing.assert_allclose(out, 7.0, rtol=1e-5)

    def test_gradient_ramp(self):
        # linear ramp in x: averaged samples reproduce the ramp center
        H = W = 16
        ramp = np.tile(np.arange(W, dtype=np.float32), (H, 1))
        x = ramp[None, None]
        rois = np.array([[4.0, 4.0, 12.0, 12.0]], np.float32)
        out = vops.roi_align(_t(x), _t(rois), output_size=2,
                             aligned=False).numpy()[0, 0]
        # columns centered at x = 4 + {1, 3}/4 * 8 -> 6, 10
        np.testing.assert_allclose(out[:, 0], 6.0, atol=0.3)
        np.testing.assert_allclose(out[:, 1], 10.0, atol=0.3)

    @pytest.mark.slow  # 9 s RoiAlign duplicate: test_gradient_ramp above is
    # the default rep (870s cap)
    def test_multi_image_batch(self):
        x = np.stack([np.full((1, 8, 8), 1.0), np.full((1, 8, 8), 2.0)]) \
            .astype(np.float32)
        rois = np.array([[0, 0, 4, 4], [0, 0, 4, 4]], np.float32)
        out = vops.roi_align(_t(x), _t(rois), boxes_num=_t(np.array([1, 1])),
                             output_size=2).numpy()
        np.testing.assert_allclose(out[0], 1.0, rtol=1e-5)
        np.testing.assert_allclose(out[1], 2.0, rtol=1e-5)


class TestBoxCoderFpn:
    def test_encode_decode_roundtrip(self):
        priors = np.array([[0, 0, 10, 10], [5, 5, 20, 25]], np.float32)
        targets = np.array([[1, 1, 9, 11], [6, 4, 18, 22]], np.float32)
        var = np.ones((4,), np.float32)
        enc = vops.box_coder(_t(priors), _t(var), _t(targets),
                             code_type="encode_center_size")
        dec = vops.box_coder(_t(priors), _t(var), enc,
                             code_type="decode_center_size").numpy()
        np.testing.assert_allclose(dec, targets, rtol=1e-4, atol=1e-4)

    def test_fpn_levels(self):
        rois = np.array([[0, 0, 56, 56], [0, 0, 224, 224], [0, 0, 448, 448]],
                        np.float32)
        lvl = vops.distribute_fpn_proposals(_t(rois), 2, 5, 4, 224).numpy()
        np.testing.assert_array_equal(lvl, [2, 4, 5])


class TestLuUnpack:
    def test_reconstructs_input(self):
        rng = np.random.RandomState(1)
        a = rng.randn(5, 5).astype(np.float32)
        lu_mat, piv = paddle.lu(_t(a))
        P, L, U = paddle.lu_unpack(lu_mat, piv)
        rec = P.numpy() @ L.numpy() @ U.numpy()
        np.testing.assert_allclose(rec, a, rtol=1e-3, atol=1e-4)

    def test_batched(self):
        rng = np.random.RandomState(2)
        a = rng.randn(3, 4, 4).astype(np.float32)
        lu_mat, piv = paddle.lu(_t(a))
        P, L, U = paddle.lu_unpack(lu_mat, piv)
        rec = np.einsum("bij,bjk,bkl->bil", P.numpy(), L.numpy(), U.numpy())
        np.testing.assert_allclose(rec, a, rtol=1e-3, atol=1e-4)

    def test_flags_return_none(self):
        rng = np.random.RandomState(3)
        a = rng.randn(4, 4).astype(np.float32)
        lu_mat, piv = paddle.lu(_t(a))
        P, L, U = paddle.lu_unpack(lu_mat, piv, unpack_ludata=False)
        assert L is None and U is None and P is not None
        P2, L2, U2 = paddle.lu_unpack(lu_mat, piv, unpack_pivots=False)
        assert P2 is None and L2 is not None


class TestDetectionOpsR4:
    """roi_pool / prior_box / yolo_box (reference detection ops †)."""

    def test_roi_pool_hand_checked_reference_quantization(self):
        """Reference bins: roi span end-start+1 = 5, bin 2.5, cells
        [floor(i*2.5), ceil((i+1)*2.5)) = [0,3) and [2,5) (overlapping)."""
        x = np.arange(64, dtype=np.float32).reshape(1, 1, 8, 8)
        boxes = np.asarray([[0., 0., 4., 4.]], np.float32)
        out = vops.roi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                            output_size=2).numpy()
        np.testing.assert_allclose(out.reshape(2, 2),
                                   [[18., 20.], [34., 36.]])

    def test_roi_pool_overflow_and_empty_guarded(self):
        x = np.arange(64, dtype=np.float32).reshape(1, 1, 8, 8)
        # roi extends past the 8x8 map: clamped, never the NEG sentinel
        out = vops.roi_pool(paddle.to_tensor(x),
                            paddle.to_tensor(
                                np.asarray([[0., 0., 20., 20.]], np.float32)),
                            output_size=3).numpy()
        assert np.isfinite(out).all() and out.min() >= 0
        assert out.max() == 63.0
        # batch>1 without boxes_num must raise like roi_align
        import pytest as _pt
        with _pt.raises(ValueError, match="boxes_num"):
            vops.roi_pool(paddle.to_tensor(np.zeros((2, 1, 8, 8),
                                                    np.float32)),
                          paddle.to_tensor(
                              np.asarray([[0., 0., 2., 2.]], np.float32)))

    def test_roi_pool_batched_with_boxes_num(self):
        x = np.random.RandomState(0).randn(2, 3, 8, 8).astype(np.float32)
        boxes = np.asarray([[0, 0, 8, 8], [2, 2, 6, 6], [0, 0, 4, 4]],
                           np.float32)
        out = vops.roi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                           boxes_num=paddle.to_tensor(
                               np.asarray([2, 1], np.int32)),
                           output_size=2).numpy()
        assert out.shape == (3, 3, 2, 2)
        # roi 2 reads image 1; reference cell (1,1) spans rows/cols [2,5)
        np.testing.assert_allclose(out[2, :, 1, 1],
                                   x[1, :, 2:5, 2:5].max(axis=(1, 2)))

    def test_prior_box_shapes_and_geometry(self):
        feat = paddle.to_tensor(np.zeros((1, 3, 4, 4), np.float32))
        img = paddle.to_tensor(np.zeros((1, 3, 32, 32), np.float32))
        pb, pv = vops.prior_box(feat, img, min_sizes=[8.0],
                               aspect_ratios=[2.0], flip=True)
        assert pb.shape == [4, 4, 3, 4] and pv.shape == [4, 4, 3, 4]
        b = pb.numpy()
        # first prior of cell (0,0): square of size 8 centered at 4px
        np.testing.assert_allclose(
            b[0, 0, 0], [0.0, 0.0, 8 / 32, 8 / 32], atol=1e-6)
        # aspect-2 prior is wider than tall
        ar2 = b[0, 0, 1]
        assert (ar2[2] - ar2[0]) > (ar2[3] - ar2[1])
        # variances broadcast the given 4-vector
        np.testing.assert_allclose(pv.numpy()[2, 3, 1],
                                   [0.1, 0.1, 0.2, 0.2])
        # max-size prior position honors min_max_aspect_ratios_order:
        # default False -> [min, ars..., max]; True -> [min, max, ars...]
        pb_f, _ = vops.prior_box(feat, img, min_sizes=[8.0],
                                 max_sizes=[16.0], aspect_ratios=[2.0])
        pb_t, _ = vops.prior_box(feat, img, min_sizes=[8.0],
                                 max_sizes=[16.0], aspect_ratios=[2.0],
                                 min_max_aspect_ratios_order=True)
        big = np.sqrt(8.0 * 16.0) / 32
        bf, bt = pb_f.numpy()[0, 0], pb_t.numpy()[0, 0]
        np.testing.assert_allclose(bf[-1][2] - bf[-1][0], big, atol=1e-6)
        np.testing.assert_allclose(bt[1][2] - bt[1][0], big, atol=1e-6)

    def test_yolo_box_iou_aware_rejected(self):
        import pytest as _pt
        with _pt.raises(NotImplementedError, match="iou_aware"):
            vops.yolo_box(paddle.to_tensor(np.zeros((1, 27, 4, 4),
                                                    np.float32)),
                          paddle.to_tensor(np.asarray([[64, 64]], np.int32)),
                          anchors=[10, 13, 16, 30, 33, 23], class_num=4,
                          iou_aware=True)

    @pytest.mark.slow  # 6 s decode-properties duplicate: the roi_pool and
    # prior_box reps in this class run by default (870s cap)
    def test_yolo_box_decode_properties(self):
        rng = np.random.RandomState(1)
        A, C, H, W = 3, 4, 4, 4
        x = rng.randn(2, A * (5 + C), H, W).astype(np.float32)
        img_size = np.asarray([[64, 64], [32, 48]], np.int32)
        b, s = vops.yolo_box(paddle.to_tensor(x),
                            paddle.to_tensor(img_size),
                            anchors=[10, 13, 16, 30, 33, 23], class_num=C,
                            conf_thresh=0.0)
        assert b.shape == [2, A * H * W, 4] and s.shape == [2, A * H * W, C]
        bn, sn = b.numpy(), s.numpy()
        # clipped into each image's pixel bounds
        assert bn[0].min() >= 0 and bn[0, :, [0, 2]].max() <= 63
        assert bn[1, :, [1, 3]].max() <= 31 and bn[1, :, [0, 2]].max() <= 47
        # scores are sigmoid(conf)*sigmoid(cls) in [0, 1]
        assert sn.min() >= 0 and sn.max() <= 1
        # high conf_thresh zeroes everything
        b0, s0 = vops.yolo_box(paddle.to_tensor(x),
                              paddle.to_tensor(img_size),
                              anchors=[10, 13, 16, 30, 33, 23], class_num=C,
                              conf_thresh=1.1)
        assert float(np.abs(b0.numpy()).max()) == 0.0


class TestGenerateProposals:
    def test_invariants_and_static_shapes(self):
        """RPN decode->clip->min-size->NMS->top-k (reference
        generate_proposals_v2 †): static [N, post_n] padding with
        rois_num giving the valid counts; kept boxes are inside the
        image, score-sorted, and pairwise under the NMS threshold."""
        rng = np.random.RandomState(0)
        N, A, H, W = 2, 3, 4, 4
        scores = rng.rand(N, A, H, W).astype(np.float32)
        deltas = (rng.randn(N, 4 * A, H, W) * 0.1).astype(np.float32)
        img = np.asarray([[64, 64], [64, 64]], np.float32)
        anchors = np.zeros((H, W, A, 4), np.float32)
        for i in range(H):
            for j in range(W):
                for a in range(A):
                    cx, cy = j * 16 + 8, i * 16 + 8
                    sz = 8 * (a + 1)
                    anchors[i, j, a] = [cx - sz, cy - sz, cx + sz, cy + sz]
        var = np.full((H, W, A, 4), 1.0, np.float32)
        rois, probs, num = vops.generate_proposals(
            _t(scores), _t(deltas), _t(img), _t(anchors), _t(var),
            pre_nms_top_n=20, post_nms_top_n=8, nms_thresh=0.7,
            return_rois_num=True)
        rois, probs, num = rois.numpy(), probs.numpy(), num.numpy()
        assert rois.shape == (2, 8, 4) and probs.shape == (2, 8)
        for b in range(N):
            nb = int(num[b])
            assert 1 <= nb <= 8
            v = rois[b, :nb]
            assert (v[:, 0] <= v[:, 2] + 1e-5).all()
            assert v.min() >= -1e-5 and v.max() <= 64 + 1e-4
            assert (np.diff(probs[b, :nb]) <= 1e-6).all()
            iou = vops.box_iou(_t(v), _t(v)).numpy() - np.eye(nb)
            assert iou.max() <= 0.7 + 1e-5
        # adaptive-NMS eta is honestly rejected, not silently ignored
        with pytest.raises(NotImplementedError):
            vops.generate_proposals(
                _t(scores), _t(deltas), _t(img), _t(anchors), _t(var),
                eta=0.9)
