"""Vision transform r4 batch (reference
``python/paddle/vision/transforms/transforms.py`` †) — torch(vision)-free
oracles: hand-computable invariants + torch functional where available."""
import numpy as np
import pytest

import paddle_tpu.vision.transforms as T


def _img(seed=0, h=8, w=10):
    return (np.random.RandomState(seed).rand(h, w, 3) * 255) \
        .astype(np.uint8)


class TestColorOps:
    def test_adjust_brightness_scales(self):
        img = _img()
        out = T.adjust_brightness(img, 2.0)
        np.testing.assert_array_equal(
            out, np.clip(img.astype(np.float32) * 2, 0, 255)
            .astype(np.uint8))

    def test_adjust_contrast_identity_and_zero(self):
        img = _img(1)
        np.testing.assert_allclose(T.adjust_contrast(img, 1.0), img)
        flat = T.adjust_contrast(img, 0.0).astype(np.float32)
        assert flat.std() < 1.0  # collapses to the mean gray

    def test_adjust_saturation_zero_is_grayscale(self):
        img = _img(2)
        out = T.adjust_saturation(img, 0.0).astype(np.float32)
        np.testing.assert_allclose(out[..., 0], out[..., 1], atol=1.0)
        np.testing.assert_allclose(out[..., 1], out[..., 2], atol=1.0)

    def test_adjust_hue_roundtrip_and_identity(self):
        img = _img(3)
        np.testing.assert_allclose(T.adjust_hue(img, 0.0), img, atol=2.0)
        # full-turn rotation (0.5 twice) returns close to the original
        twice = T.adjust_hue(T.adjust_hue(img, 0.5), 0.5)
        np.testing.assert_allclose(twice, img, atol=3.0)
        with pytest.raises(ValueError):
            T.adjust_hue(img, 0.7)

    def test_hue_matches_torch(self):
        torch = pytest.importorskip("torch")
        try:
            from torchvision.transforms import functional as TVF
        except Exception:
            pytest.skip("torchvision unavailable")
        img = _img(4)
        got = T.adjust_hue(img, 0.2).astype(np.float32)
        want = np.asarray(TVF.adjust_hue(
            torch.tensor(img.transpose(2, 0, 1)), 0.2)) \
            .transpose(1, 2, 0).astype(np.float32)
        np.testing.assert_allclose(got, want, atol=3.0)

    def test_grayscale(self):
        img = _img(5)
        g1 = T.Grayscale(1)(img)
        assert g1.shape == (8, 10, 1)
        g3 = T.Grayscale(3)(img)
        np.testing.assert_array_equal(g3[..., 0], g3[..., 1])


class TestGeometry:
    def test_pad_constant_and_modes(self):
        img = _img(6)
        out = T.Pad((1, 2, 3, 4), fill=7)(img)  # l, t, r, b
        assert out.shape == (8 + 2 + 4, 10 + 1 + 3, 3)
        assert (out[0] == 7).all() and (out[:, 0] == 7).all()
        edge = T.Pad(2, padding_mode="edge")(img)
        np.testing.assert_array_equal(edge[0, 2:-2], img[0])

    def test_rotate_90_matches_rot90(self):
        img = _img(7, h=9, w=9)
        out = T.rotate(img, 90, interpolation="nearest")
        np.testing.assert_array_equal(out, np.rot90(img, 1))

    def test_rotate_zero_identity_bilinear(self):
        img = _img(8)
        np.testing.assert_allclose(
            T.rotate(img, 0.0, interpolation="bilinear"), img, atol=1e-3)

    def test_random_rotation_bounds(self):
        img = _img(9)
        out = T.RandomRotation(0.0)(img)  # zero range = identity
        np.testing.assert_array_equal(out, img)

    def test_random_rotation_forwards_expand(self):
        # advisor r4: expand=True was accepted but silently dropped
        img = _img(10, h=8, w=16)
        out = T.RandomRotation((90, 90), expand=True)(img)
        assert out.shape[:2] == (16, 8), out.shape

    def test_random_erasing(self):
        img = np.full((16, 16, 3), 200, np.uint8)
        out = T.RandomErasing(prob=1.0, value=0)(img)
        assert (out == 0).any() and (out == 200).any()
        same = T.RandomErasing(prob=0.0)(img)
        np.testing.assert_array_equal(same, img)

    def test_gaussian_blur_preserves_mean_and_smooths(self):
        rng = np.random.RandomState(10)
        img = (rng.rand(32, 32, 3) * 255).astype(np.uint8)
        out = T.GaussianBlur(5, sigma=1.5)(img).astype(np.float32)
        assert abs(out.mean() - img.astype(np.float32).mean()) < 3.0
        # variance must drop under smoothing
        assert out.std() < img.astype(np.float32).std()


class TestComposedJitter:
    def test_color_jitter_runs_and_stays_in_range(self):
        img = _img(11)
        out = T.ColorJitter(0.3, 0.3, 0.3, 0.2)(img)
        a = np.asarray(out)
        assert a.shape == img.shape
        assert a.min() >= 0 and a.max() <= 255

    def test_color_jitter_accepts_range_tuples(self):
        img = _img(12)
        out = T.ColorJitter(brightness=(0.5, 1.5), contrast=(0.8, 1.2),
                            saturation=(0.9, 1.1), hue=(-0.1, 0.1))(img)
        assert np.asarray(out).shape == img.shape

    def test_rotate_expand_enlarges_canvas(self):
        img = _img(13, h=8, w=12)
        out = T.rotate(img, 45, expand=True)
        assert out.shape[0] > 8 and out.shape[1] > 12
        # 90-degree expand swaps dimensions exactly
        out90 = T.rotate(img, 90, expand=True, interpolation="nearest")
        assert out90.shape[:2] == (12, 8)

    def test_gaussian_blur_rejects_even_kernel(self):
        with pytest.raises(ValueError, match="odd"):
            T.GaussianBlur(4)
