"""Device-plane XPlane parsing pinned against a SYNTHETIC TPU trace.

The CI xplane test parses a real CPU-backend trace, but the device plane
(`device_only=True`, the branch `bench.py --trace` tries first on the real
chip) had only ever been exercised against host planes. This encodes an
XSpace in raw protobuf wire format with TPU-style device planes — same
field numbers the parser documents — so the device-only filter, the
metadata display_name precedence, and multi-plane aggregation are all
proven without a chip.
"""
import os

from paddle_tpu.profiler.xplane import op_statistics, parse_xplane, summarize


def _varint(n):
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        out += bytes([b | (0x80 if n else 0)])
        if not n:
            return out


def _field(fno, payload):
    if isinstance(payload, int):
        return _varint((fno << 3) | 0) + _varint(payload)
    return _varint((fno << 3) | 2) + _varint(len(payload)) + payload


def _event(meta_id, dur_ps, offset_ps=0):
    return _field(1, meta_id) + _field(2, offset_ps) + _field(3, dur_ps)


def _event_metadata(mid, name, display_name=None):
    m = _field(1, mid) + _field(2, name.encode())
    if display_name is not None:
        m += _field(3, display_name.encode())
    return m


def _meta_entry(mid, name, display_name=None):
    return _field(1, mid) + _field(2, _event_metadata(mid, name,
                                                      display_name))


def _plane(name, meta_entries, lines):
    buf = _field(2, name.encode())
    for lb in lines:
        buf += _field(3, lb)
    for me in meta_entries:
        buf += _field(4, me)
    return buf


def _line(events, line_id=1):
    buf = _field(1, line_id)
    for e in events:
        buf += _field(4, e)
    return buf


def _write_space(tmp_path, planes):
    space = b"".join(_field(1, p) for p in planes)
    d = tmp_path / "plugins" / "profile" / "run"
    os.makedirs(d)
    (d / "host.xplane.pb").write_bytes(space)
    return str(tmp_path)


class TestDevicePlaneParsing:
    def _make_trace(self, tmp_path):
        device = _plane(
            "/device:TPU:0 (chip 0 core 0)",
            [_meta_entry(7, "fusion.42", "fused_matmul_add"),
             _meta_entry(9, "copy.3")],
            # two lines (XLA Modules / XLA Ops style): fusion appears twice
            [_line([_event(7, 5_000_000_000), _event(9, 1_000_000_000)]),
             _line([_event(7, 2_000_000_000)], line_id=2)])
        host = _plane(
            "/host:CPU",
            [_meta_entry(1, "python_thread")],
            [_line([_event(1, 9_000_000_000)])])
        return _write_space(tmp_path, [device, host])

    def test_device_only_filters_host(self, tmp_path):
        rows = op_statistics(self._make_trace(tmp_path), device_only=True)
        assert {r["name"] for r in rows} == {"fused_matmul_add", "copy.3"}
        assert all("TPU" in r["plane"] for r in rows)

    def test_aggregation_and_display_name(self, tmp_path):
        rows = op_statistics(self._make_trace(tmp_path), device_only=True)
        fused = next(r for r in rows if r["name"] == "fused_matmul_add")
        # 5ms + 2ms across two lines, display_name wins over name
        assert fused["count"] == 2
        assert abs(fused["total_ms"] - 7.0) < 1e-9
        assert rows[0]["name"] == "fused_matmul_add"  # sorted by total

    def test_host_plane_included_when_not_device_only(self, tmp_path):
        rows = op_statistics(self._make_trace(tmp_path), device_only=False)
        assert any(r["name"] == "python_thread" for r in rows)

    def test_parse_xplane_shape(self, tmp_path):
        d = self._make_trace(tmp_path)
        path = os.path.join(d, "plugins", "profile", "run", "host.xplane.pb")
        planes = parse_xplane(path)
        assert [p["name"] for p in planes] == [
            "/device:TPU:0 (chip 0 core 0)", "/host:CPU"]

    def test_summarize_renders(self, tmp_path):
        out = summarize(self._make_trace(tmp_path))
        assert "fused_matmul_add" in out and "total_ms" in out
